"""Adaptive defense plane tests (ops/trust.py, docs/DEFENSES.md).

Unit level: plan validation + CLI knobs, TrustLedger determinism (two
ledgers fed the same block/decision sequence are bit-identical), the
chain walk's decline-path semantics (eligible absence IS the reject
signal), the slow-trust ramp (graduation, absence reset, duty-cycle
gate), the proven gate on the one-shot vetoes, the temporal-drift
scorer on verdict-coupled vs honest walks, ensemble hysteresis
(hold-down, no flap), and the FoolsGold small-N cluster-size fix.

Integration level (`-m defense` isolates): a clean ENSEMBLE cluster
accrues ZERO false rejections (the headline acceptance criterion), the
defaults-off guard (any other defense arms no ledger, emits no trust
metrics), and verdict-stream + ledger identity across the TCP and
hive-loopback transport layouts.
"""

import asyncio
import math

import numpy as np
import pytest

from biscotti_tpu.config import BiscottiConfig, Defense, Timeouts
from biscotti_tpu.ops import trust as trustlib
from biscotti_tpu.ops.trust import TrustLedger, TrustPlan
from biscotti_tpu.runtime.peer import PeerAgent
from biscotti_tpu.tools.chaos import chain_oracle

FAST = Timeouts(update_s=5.0, block_s=15.0, krum_s=3.0, share_s=5.0,
                rpc_s=4.0)


def _cfg(i, n, port, **kw):
    base = dict(
        node_id=i, num_nodes=n, dataset="creditcard", base_port=port,
        num_verifiers=1, num_miners=1, num_noisers=1,
        secure_agg=False, noising=False, verification=True,
        max_iterations=3, convergence_error=0.0, sample_percent=1.0,
        batch_size=8, timeouts=FAST, seed=3,
    )
    base.update(kw)
    return BiscottiConfig(**base)


def _run_cluster(cfgs):
    async def go():
        agents = [PeerAgent(c) for c in cfgs]
        results = await asyncio.gather(*(a.run() for a in agents))
        return results, agents

    return asyncio.run(go())


def _flat_cos(n, c=0.05, overrides=None):
    """n x n cosine matrix with constant off-diagonal c; overrides is
    {(i, j): value} applied symmetrically."""
    m = [[c] * n for _ in range(n)]
    for i in range(n):
        m[i][i] = 1.0
    for (i, j), v in (overrides or {}).items():
        m[i][j] = m[j][i] = v
    return m


def _neutral_decide(led, it, ids, **kw):
    """A decide() call shaped so no veto fires unless a kwarg says so."""
    n = len(ids)
    args = dict(norms=[1.0] * n, residuals=[0.5] * n, scores=[1.0] * n,
                keep=[True] * n, cos=_flat_cos(n))
    args.update(kw)
    return led.decide(it, ids, **args)


# ---------------------------------------------------------------- units


def test_plan_validation_and_cli_knobs():
    TrustPlan().validate()  # defaults must be self-consistent
    for bad in (dict(geo_ratio=1.0), dict(sim_margin=0.0),
                dict(sim_min_pairs=0), dict(mag_band=1.0),
                dict(proven_accepts=-1), dict(proven_window=0),
                dict(drift_hi=0.2, drift_lo=0.3), dict(drift_min_obs=1),
                dict(hold_rounds=-1), dict(ramp_floor=0.0),
                dict(absence_reset=0), dict(stream_cap=0)):
        with pytest.raises(ValueError):
            TrustPlan(**bad).validate()

    import argparse

    ap = argparse.ArgumentParser()
    BiscottiConfig.add_args(ap)
    ns = ap.parse_args([
        "--node-id", "0", "--num-nodes", "4", "--defense", "ENSEMBLE",
        "--trust-geo-ratio", "3.5", "--trust-mag-band", "4.0",
        "--trust-hold", "5", "--trust-ramp-rounds", "6",
        "--trust-ramp-floor", "0.25", "--trust-absence-reset", "2",
        "--fg-min-cluster", "2",
    ])
    cfg = BiscottiConfig.from_args(ns)
    assert cfg.defense == Defense.ENSEMBLE
    assert cfg.trust_plan.geo_ratio == 3.5
    assert cfg.trust_plan.mag_band == 4.0
    assert cfg.trust_plan.hold_rounds == 5
    assert cfg.trust_plan.ramp_rounds == 6
    assert cfg.trust_plan.ramp_floor == 0.25
    assert cfg.trust_plan.absence_reset == 2
    assert cfg.fg_min_cluster == 2
    # knobs not flagged keep the plan defaults
    assert cfg.trust_plan.sim_margin == TrustPlan.sim_margin

    with pytest.raises(ValueError):
        _cfg(0, 4, 15000, defense=Defense.ENSEMBLE, fedsys=True)
    with pytest.raises(ValueError):
        _cfg(0, 4, 15000, fg_min_cluster=0)


def test_ledger_determinism_and_replay_guard():
    """Two ledgers fed the identical block/decision sequence are
    bit-identical — the property the TCP-vs-hive criterion rests on —
    and replayed / out-of-order blocks are ignored."""

    def feed(led):
        led.sync_block(0, {i: True for i in range(6)}, committee={6, 7})
        _neutral_decide(led, 1, list(range(6)))
        led.sync_block(1, {0: True, 1: False, 3: True}, committee={2, 5})
        _neutral_decide(led, 2, [0, 1, 3, 4],
                        norms=[1.0, 9.0, 1.1, 0.9],
                        scores=[1.0, 30.0, 1.2, 0.8],
                        keep=[True, False, True, True])
        led.sync_block(2, {}, committee=None)    # empty: no signal

    a = TrustLedger(TrustPlan(), 8)
    b = TrustLedger(TrustPlan(), 8)
    feed(a)
    feed(b)
    assert a.snapshot() == b.snapshot()
    assert a.trust_scores() == b.trust_scores()

    snap = a.snapshot()
    a.sync_block(1, {0: False, 1: True}, committee=None)  # replay
    a.sync_block(0, {5: False}, committee=None)           # out-of-order
    assert a.snapshot() == snap
    assert a._peers[0].walk[1] is True


def test_chain_walk_decline_path_semantics():
    """A DEFENSE rejection leaves NO chain record (the worker declines),
    so the walk must read eligible absence as the reject signal, while
    committee membership and unknown electorates carry none."""
    led = TrustLedger(TrustPlan(), 6)
    led.sync_block(0, {0: True, 1: False}, committee={2, 3})
    assert led._peers[0].walk[0] is True
    assert led._peers[1].walk[0] is False          # miner-stage reject
    assert led._peers[4].walk[0] is False          # eligible + absent
    assert led._peers.get(2) is None               # committee: no signal
    led.sync_block(1, {0: True}, committee=None)   # unknown electorate
    assert 1 not in led._peers[4].walk


def test_slow_trust_ramp_graduation_and_absence_reset():
    plan = TrustPlan(ramp_rounds=4, ramp_floor=0.4, absence_reset=3)
    led = TrustLedger(plan, 4)
    assert led.weight(3) == 1.0          # unseen: grandfathered
    led.seed_fresh([3])
    assert led.weight(3) == pytest.approx(0.4)
    for it in range(4):                  # accepted blocks ramp it up
        led.sync_block(it, {3: True}, committee=set())
    assert led.weight(3) == 1.0 and led._peers[3].ramp is None
    # graduated identity disappearing for absence_reset eligible rounds
    # restarts the ramp — the sybil-recycle trigger
    for it in range(4, 7):
        led.sync_block(it, {0: True}, committee=set())
    assert led._peers[3].ramp == 0 and led._peers[3].resets == 1
    assert led.weight(3) == pytest.approx(0.4)
    # seed_fresh never demotes an identity with accepted history
    led2 = TrustLedger(plan, 4)
    led2.sync_block(0, {1: True}, committee=set())
    led2.seed_fresh([1])
    assert led2.weight(1) == 1.0


def test_slow_trust_duty_cycle_gates_without_arming_hold():
    """A ramping identity is throttled to its weight's duty cycle; the
    pure slow_trust vote must NOT arm the hysteresis hold, or a fresh
    identity could never accrue the accepts it needs to graduate.

    Credit is CHAIN-derived: each round's decision is followed by the
    block it produced — an accepted record consumes the pass, a
    throttled round banks its weight as an eligible absence."""
    led = TrustLedger(TrustPlan(ramp_rounds=4, ramp_floor=0.4), 2)
    led.seed_fresh([0])
    walk = []
    for it in range(5):
        accepts, votes, _ = _neutral_decide(led, it, [0, 1])
        walk.append((accepts[0], tuple(votes[0])))
        assert accepts[1] and not votes[1]       # veteran untouched
        records = {1: True}
        if accepts[0]:
            records[0] = True            # the pass lands on the chain
        led.sync_block(it, records, committee=set())
    # credit 0.4 / 0.8 / 1.2->accept(->0.75) / 1.3->accept
    assert walk == [(False, ("slow_trust",)), (False, ("slow_trust",)),
                    (True, ()), (False, ("slow_trust",)), (True, ())]
    assert led._peers[0].hold == 0


def test_slow_trust_verdict_unanimous_across_churned_committees():
    """Chain-derived credit (ROADMAP item 2b residual): verifiers that
    folded the same committed blocks issue the IDENTICAL slow_trust
    verdict regardless of which rounds each of them happened to decide.
    Before this change the credit accumulator mutated inside decide(),
    so a freshly seated verifier on a churned committee disagreed with
    a veteran one about a ramping identity — a per-round verdict split
    the protocol's majority-approval then had to paper over."""
    plan = TrustPlan(ramp_rounds=4, ramp_floor=0.4)
    veteran = TrustLedger(plan, 3)   # decides EVERY round
    joiner = TrustLedger(plan, 3)    # seated late: only folds the chain
    for led in (veteran, joiner):
        led.seed_fresh([0])
    for it in range(6):
        accepts, _, _ = _neutral_decide(veteran, it, [0, 1])
        records = {1: True}
        if accepts[0]:
            records[0] = True
        for led in (veteran, joiner):
            led.sync_block(it, records, committee=set())
    assert veteran._peers[0].credit == joiner._peers[0].credit
    va = _neutral_decide(veteran, 6, [0, 1])
    ja = _neutral_decide(joiner, 6, [0, 1])
    assert va == ja
    # and deciding is side-effect-free on the credit state: replaying
    # the same decision yields the same verdict (idempotent verdicts
    # are what make committee rotation safe)
    assert _neutral_decide(joiner, 6, [0, 1]) == ja


def test_proven_gate_exempts_veterans_from_one_shot_vetoes():
    """Same outlier geometry/magnitude, opposite verdicts: an identity
    with a majority-accepted recent walk is exempt from the one-shot
    vetoes, one with no earned history is not — and an attacker cannot
    fake the walk because rejection leaves no record to graduate on."""
    led = TrustLedger(TrustPlan(proven_accepts=2), 8)
    for it in range(2):
        # peer 6 is eligible yet absent -> negative walk evidence, so
        # neither proven nor committee-clean
        led.sync_block(it, {i: True for i in range(5)},
                       committee={5, 7})
    assert led.proven(0)
    assert not led.proven(6) and not led.committee_clean(6)
    ids = [0, 1, 2, 3, 6]
    outlier = dict(
        norms=[50.0, 1.0, 1.1, 0.9, 50.0],
        scores=[100.0, 1.0, 1.2, 0.8, 100.0],
        keep=[False, True, True, True, False],
    )
    accepts, votes, _ = _neutral_decide(led, 3, ids, **outlier)
    assert accepts[0] and not votes[0]           # proven: gated
    assert not accepts[4]                        # fresh: full scrutiny
    assert set(votes[4]) == {"geometry", "magnitude"}
    # one-sided magnitude: a scaled-DOWN probe carries proportionally
    # little poison and must not fire the veto on its own
    _, votes2, _ = _neutral_decide(
        led, 4, ids, norms=[1.0, 1.0, 1.1, 0.9, 0.01])
    assert "magnitude" not in votes2[4]


def test_committee_clean_exemption():
    """An empty walk after real blocks settled means every absence was
    committee duty — no negative evidence, so the one-shot vetoes stay
    gated. An eligible absence (the decline signal) ends the exemption,
    and at genesis (no blocks) nobody is exempt."""
    led = TrustLedger(TrustPlan(), 6)
    assert not led.committee_clean(0)            # genesis: scrutinise
    led.sync_block(0, {0: True, 1: True}, committee={4, 5})
    led.sync_block(1, {0: True, 1: True}, committee={4, 5})
    assert led.committee_clean(4)
    assert not led.committee_clean(2)            # eligible-absent
    ids = [0, 1, 4, 2]
    accepts, votes, _ = _neutral_decide(
        led, 2, ids,
        scores=[1.0, 1.1, 80.0, 80.0],
        keep=[True, True, False, False])
    assert accepts[2] and not votes[2]           # committee-clean: gated
    assert not accepts[3] and votes[3] == ["geometry"]


def test_similarity_veto_and_min_pairs_guard():
    plan = TrustPlan(sim_margin=0.15, sim_mad_mult=6.0, sim_min_pairs=3)
    led = TrustLedger(plan, 8)
    n = 6
    # a colluding pair at cos 0.9 against an honest baseline of 0.05;
    # keep covers 4 honest peers -> 6 calibration pairs
    cos = _flat_cos(n, 0.05, {(4, 5): 0.9})
    accepts, votes, detail = _neutral_decide(
        led, 0, list(range(n)), cos=cos,
        keep=[True, True, True, True, False, False])
    assert accepts[:4] == [True] * 4
    assert not accepts[4] and not accepts[5]
    assert votes[4] == ["similarity"] and votes[5] == ["similarity"]
    assert detail["sim_bar"] < 0.9
    # a pool too small for a usable calibration sample disables the
    # veto instead of trusting a single-cosine bar
    led2 = TrustLedger(plan, 8)
    _, _, d2 = _neutral_decide(led2, 0, [0, 1, 2],
                               cos=_flat_cos(3, 0.8),
                               keep=[True, True, False])
    assert d2["sim_bar"] == 2.0


def test_drift_flags_verdict_coupled_walk_not_honest_noise():
    """The cross-round consistency scorer: a hugger's residual moves
    WITH its chain verdicts (up on accept, down on reject); honest
    minibatch noise is uncorrelated and spans too little range."""
    plan = TrustPlan()
    led = TrustLedger(plan, 4)
    r_hug = 1.0
    accepted = True
    for it in range(12):
        # observe this round's residual, THEN the verdict lands on chain
        # and the controller reacts for the next round — the real
        # ordering in _ensemble_mask (decide before block it commits)
        r_hon = 1.0 + 0.01 * (1 if it % 2 else -1)
        _neutral_decide(led, it, [0, 1], residuals=[r_hug, r_hon])
        led.sync_block(it, {0: accepted, 1: True}, committee=set())
        r_hug *= 1.6 if accepted else 0.5         # the hug controller
        accepted = not accepted
    assert led._peers[0].drift_score >= plan.drift_hi
    assert led._peers[0].flagged
    assert led._peers[1].drift_score == 0.0 and not led._peers[1].flagged
    assert led.trust_scores()[0] == 0.0
    # constant-verdict monotone regime: an always-rejected hugger
    # backing its scale off is equally coupled
    led2 = TrustLedger(plan, 2)
    r = 8.0
    for it in range(10):
        _neutral_decide(led2, it, [0, 1], residuals=[r, 1.0])
        led2.sync_block(it, {1: True}, committee=set())  # 0 absent
        r *= 0.6
    assert led2._peers[0].drift_score == 1.0


def test_hysteresis_hold_no_flap():
    """One veto round arms hold_rounds of continued rejection; the peer
    re-enters only after serving the full hold with no further votes."""
    led = TrustLedger(TrustPlan(hold_rounds=3), 4)
    ids = [0, 1, 2, 3]
    _, votes, _ = _neutral_decide(led, 0, ids,
                                  scores=[40.0, 1.0, 1.1, 0.9],
                                  keep=[False, True, True, True])
    assert votes[0] == ["geometry"]
    verdicts = []
    for it in range(1, 5):
        accepts, votes, _ = _neutral_decide(led, it, ids)
        verdicts.append((accepts[0], tuple(votes[0])))
    assert verdicts == [(False, ("hold",)), (False, ("hold",)),
                        (False, ("hold",)), (True, ())]


def test_foolsgold_min_cluster_gate():
    """The small-N fix: an accidental honest pair is freed by the
    cluster-size gate (a sybil CLUSTER is what FoolsGold models), a
    genuine triple is still caught, and min_cluster=1 restores the
    original kernel."""
    from biscotti_tpu.ops.robust_agg import foolsgold_accept_mask

    rng = np.random.default_rng(7)
    base = rng.normal(size=(9, 400)).astype(np.float32)
    base[7] = base[8] + 0.01 * rng.normal(size=400).astype(np.float32)
    m3 = np.asarray(foolsgold_accept_mask(base, min_cluster=3))
    m1 = np.asarray(foolsgold_accept_mask(base, min_cluster=1))
    assert m3[7] and m3[8]                 # pair freed at min_cluster=3
    assert not m1[7] and not m1[8]         # PR-1 behaviour preserved
    triple = base.copy()
    triple[6] = triple[8] + 0.01 * rng.normal(size=400).astype(np.float32)
    mt = np.asarray(foolsgold_accept_mask(triple, min_cluster=3))
    assert not mt[6] and not mt[7] and not mt[8]
    assert mt[:6].all()


def test_trust_scores_and_stream_constants():
    led = TrustLedger(TrustPlan(), 3)
    assert led.trust_scores() == {0: 1.0, 1: 1.0, 2: 1.0}
    snap = led.snapshot()
    assert snap["synced_it"] == -1 and snap["decisions"] == 0
    assert trustlib.TRUST_METRIC == "biscotti_trust_score"
    assert trustlib.VOTES_METRIC == "biscotti_defense_votes_total"
    assert set(trustlib.SCORERS) >= {"geometry", "similarity",
                                     "magnitude", "drift", "slow_trust",
                                     "hold"}


def test_pearson_constant_sides():
    assert trustlib.pearson([1.0, 1.0, 1.0], [1.0, 2.0, 3.0]) == 0.0
    assert trustlib.pearson([1.0, 2.0], [3.0]) == 0.0
    assert trustlib.pearson([1.0, 2.0, 3.0],
                            [2.0, 4.0, 6.0]) == pytest.approx(1.0)


# ----------------------------------------------- live: clean-run safety


@pytest.mark.defense
def test_ensemble_clean_run_zero_false_rejections():
    """THE acceptance criterion: honest peers under a clean ENSEMBLE run
    accrue zero false rejections and zero stake debits — every verdict
    stream row is all-accept with no votes, no identity is flagged or
    reset, and the chains stay equal."""
    n, port = 6, 15520
    results, agents = _run_cluster(
        [_cfg(i, n, port, defense=Defense.ENSEMBLE) for i in range(n)])
    eq, _, real = chain_oracle(results)
    assert eq and real >= 1
    saw_stream = False
    for a, r in zip(agents, results):
        assert a.trust is not None
        tr = r["telemetry"].get("trust")
        assert tr is not None and tr["defense"] == "ENSEMBLE"
        led = tr.get("ledger")
        if led is not None:
            assert led["flagged"] == [] and led["resets"] == {}
            assert not any(v in led["votes"] for v in
                           ("geometry", "similarity", "magnitude",
                            "drift", "hold"))
        for row in tr.get("stream", []):
            saw_stream = True
            assert all(row["accept"]), row
            assert not any(row["votes"]), row
    assert saw_stream


@pytest.mark.defense
def test_defaults_off_guard_no_ledger_no_trust_metrics():
    """`--defense KRUM` (or anything but ENSEMBLE) arms NO TrustLedger
    and emits NO trust metrics — the structural half of the off-path
    bit-identity contract. The verdict stream itself records for every
    defense (it is the attack-matrix evidence channel)."""
    n, port = 4, 15560
    results, agents = _run_cluster(
        [_cfg(i, n, port, defense=Defense.KRUM) for i in range(n)])
    eq, _, real = chain_oracle(results)
    assert eq and real >= 1
    for a, r in zip(agents, results):
        assert a.trust is None
        snap = r["telemetry"]
        assert trustlib.TRUST_METRIC not in snap["metrics"]
        assert not any(k.startswith(trustlib.VOTES_METRIC)
                       for k in snap["counters"])
        tr = snap.get("trust")
        if tr is not None:
            assert "ledger" not in tr
            assert tr["defense"] == "KRUM"


# ------------------------------------------ live: transport determinism


@pytest.mark.defense
def test_trust_state_identical_across_tcp_and_hive_loopback():
    """Same seed => bit-identical verdict streams and ledger snapshots
    on both transport layouts (TCP one-agent-per-peer vs hive loopback
    co-hosting; exact per-agent trainers so chains match by
    construction) — the ISSUE's determinism criterion."""
    from biscotti_tpu.runtime.hive import Hive

    n = 6
    tcp_results, _ = _run_cluster(
        [_cfg(i, n, 15600, defense=Defense.ENSEMBLE) for i in range(n)])
    hive = Hive(_cfg(0, n, 15660, defense=Defense.ENSEMBLE),
                hive_id="trust", batch_device=False)
    hive_results = asyncio.run(hive.run())

    assert tcp_results[0]["chain_dump"] == hive_results[0]["chain_dump"]
    for i in range(n):
        t = tcp_results[i]["telemetry"].get("trust")
        h = hive_results[i]["telemetry"].get("trust")
        assert (t is None) == (h is None)
        if t is not None:
            assert t["stream"] == h["stream"]
            assert t.get("ledger") == h.get("ledger")
