"""Mixed-version interop matrix + rolling-upgrade drills
(runtime/protocol.py, tools/chaos.py --rolling-upgrade,
docs/PROTOCOL.md): pinned-old peers among current ones finish with
equal chains while both wire dialects flow and the degradations are
traced; a wave-by-wave mid-training upgrade holds the settled-prefix
oracle end to end."""

import asyncio
import json

import pytest

from biscotti_tpu.config import BiscottiConfig, Timeouts
from biscotti_tpu.runtime import protocol
from biscotti_tpu.runtime.peer import PeerAgent
from biscotti_tpu.runtime.rpc import RPCError
from biscotti_tpu.tools import chaos, obs

FAST = Timeouts(update_s=20.0, block_s=60.0, krum_s=20.0, share_s=20.0,
                rpc_s=10.0)

pytestmark = pytest.mark.upgrade


def _cfg(i, n, port, **kw):
    base = dict(
        node_id=i, num_nodes=n, dataset="creditcard", base_port=port,
        num_verifiers=1, num_miners=1, num_noisers=1,
        secure_agg=False, noising=False, verification=False,
        max_iterations=2, convergence_error=0.0, sample_percent=1.0,
        batch_size=8, timeouts=FAST, seed=3,
    )
    base.update(kw)
    return BiscottiConfig(**base)


def _run_cluster(cfgs):
    async def go():
        agents = [PeerAgent(c) for c in cfgs]
        results = await asyncio.gather(*(a.run() for a in agents))
        return agents, results

    return asyncio.run(go())


def test_mixed_version_matrix_interops_with_observable_degradation():
    """The live matrix (docs/PROTOCOL.md): two v0-pinned peers among
    three current ones running coded+traced+overlay config. Chains must
    come out equal, BOTH dialects must appear in the wire byte counters
    (coded among new peers, raw64 toward/from the pinned ones), and the
    codec/trace/overlay degradations must be traced — a silent downgrade
    is exactly what the plane exists to forbid."""
    n, port = 5, 12750
    full = dict(wire_codec="f32+zlib", trace=True, overlay=True,
                overlay_group=2)
    cfgs = [_cfg(i, n, port, **full,
                 protocol_version=0 if i >= 3 else -1)
            for i in range(n)]
    agents, results = _run_cluster(cfgs)

    equal, common, real = chaos.chain_oracle(results)
    assert equal, "mixed-version chains diverged"
    assert real >= 1, "no real block settled across the version gap"

    merged = obs.merge_snapshots([r["telemetry"] for r in results])
    codecs_seen = set(merged["wire"]["out_by_codec"])
    assert "raw64" in codecs_seen, codecs_seen
    assert "f32+zlib" in codecs_seen, (
        f"coded dialect never flowed between current peers: {codecs_seen}")
    assert merged["counters"].get("feature_degraded", 0) > 0

    # the degradation readout names the features lost toward the pinned
    # peers: codec stages, trace stamping, and the overlay relay rows
    degraded = set()
    for r in results[:3]:
        for feats in r["telemetry"]["protocol"]["degraded"].values():
            degraded.update(feats)
    assert {"f32", "zlib", protocol.TRACE, protocol.RELAY} <= degraded, \
        degraded
    # pinned peers advertise their row, current peers the full set
    for r in results:
        snap = r["telemetry"]["protocol"]
        if r["node"] >= 3:
            assert snap["version"] == 0
            assert snap["advertised"] == ["raw64"]
        else:
            assert snap["version"] == protocol.CURRENT_VERSION
            assert protocol.TRACE in snap["advertised"]


def test_rolling_upgrade_zero_settled_divergence(capsys):
    """The rolling-upgrade drill through the chaos CLI: fleet starts
    pinned to v0, waves of 2 restart onto the current build at anchor
    rounds 2 and 4. Exit 0 IS the oracle (settled prefix equal + >= 1
    real block across the whole mixed-version span); the report must
    show every planned wave applied and every peer finishing current."""
    rc = chaos.main(["--nodes", "4", "--rounds", "6",
                     "--base-port", "12850", "--rolling-upgrade", "0",
                     "--upgrade-period", "2", "--upgrade-wave", "2",
                     "--codec", "f32+zlib"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0, report
    ru = report["rolling_upgrade"]
    assert ru["from_version"] == 0
    assert ru["to_version"] == protocol.CURRENT_VERSION
    assert ru["waves"] == [[2, [1, 2]], [4, [3]]]
    assert sorted(ru["applied"]) == [[2, 1], [2, 2], [4, 3]]
    assert set(ru["final_versions"].values()) == \
        {protocol.CURRENT_VERSION}
    # the mixed span actually degraded features before the waves landed
    assert report["cluster"]["counters"].get("feature_degraded", 0) > 0
    assert report["settled_prefix_equal"] and report["real_blocks"] >= 1


@pytest.mark.slow
def test_rolling_upgrade_acceptance_n8_secure_agg(capsys):
    """The ISSUE-18 acceptance drill: N=8 under secure aggregation,
    wave-by-wave upgrade from v0 mid-training, zero settled-prefix
    divergence and an upgrade timeline in the report."""
    rc = chaos.main(["--nodes", "8", "--rounds", "8",
                     "--base-port", "12950", "--rolling-upgrade", "0",
                     "--upgrade-period", "2", "--upgrade-wave", "3",
                     "--secure-agg", "1", "--codec", "f32+zlib"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0, report
    ru = report["rolling_upgrade"]
    assert [w[0] for w in ru["waves"]] == [2, 4, 6]
    assert len(ru["applied"]) == 7  # every non-anchor peer upgraded
    assert set(ru["final_versions"].values()) == \
        {protocol.CURRENT_VERSION}
    assert report["settled_prefix_equal"] and report["real_blocks"] >= 1


@pytest.mark.parametrize("argv", [
    # from-current is a no-op drill (tracks CURRENT_VERSION as it grows)
    ["--rolling-upgrade", str(protocol.CURRENT_VERSION)],
    ["--rolling-upgrade", "0", "--protocol-version", "1"],  # conflicting
    ["--protocol-version", "99"],          # beyond the table
    ["--rolling-upgrade", "0", "--rounds", "2"],  # waves outlive the run
])
def test_chaos_refuses_mislabeled_upgrade_runs(argv):
    with pytest.raises(SystemExit) as exc:
        chaos.main(["--nodes", "4"] + argv)
    assert exc.value.code == 2


def test_v7_pin_answers_elastic_fleet_rpcs_unknown_method():
    """The v8 rows degrade like every gated message before them: a
    v7-pinned build IS the old build for `GetMigrationTicket` and
    `DkgDeal` — its dispatch gate answers both `unknown method` — and a
    current peer that saw the pinned hello records the lost `migrate` /
    `dkg` features in the traced+counted degradation readout rather
    than failing its drain or its ceremony silently."""
    pinned = PeerAgent(_cfg(0, 2, 13050, protocol_version=7))
    assert protocol.MIGRATE not in pinned.caps
    assert protocol.DKG not in pinned.caps
    for mt in ("GetMigrationTicket", "DkgDeal"):
        assert not protocol.serves(pinned.caps, mt)
        with pytest.raises(RPCError, match=f"unknown method {mt}"):
            asyncio.run(pinned._handle(mt, {}, {}))
    cur = PeerAgent(_cfg(1, 2, 13055))
    assert {protocol.MIGRATE, protocol.DKG} <= cur.caps
    for mt in ("GetMigrationTicket", "DkgDeal"):
        assert protocol.serves(cur.caps, mt)
    before = cur.counters.get("feature_degraded", 0)
    cur._record_caps(0, sorted(pinned.caps))
    assert {protocol.MIGRATE, protocol.DKG} <= cur._degraded_seen[0]
    assert cur.counters.get("feature_degraded", 0) >= before + 2
    # an unauthorized drain on a CURRENT build is refused by the token
    # gate, not by the protocol row — distinct, deliberate errors
    with pytest.raises(RPCError, match="migration not authorized"):
        asyncio.run(cur._handle("GetMigrationTicket", {}, {}))
