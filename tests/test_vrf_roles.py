"""Stage-5 tests: Ed25519 group law, ECVRF prove/verify, stake lottery and
prime-coded role election (deterministic fixtures in the spirit of the
reference's vrf_main.go inspection harness; ref: DistSys/vrf_main.go:1-152)."""

import hashlib

import pytest

from biscotti_tpu.crypto import ed25519 as ed
from biscotti_tpu.crypto.vrf import PROOF_LEN, VRFKey, verify
from biscotti_tpu.parallel import roles as R


# ------------------------------------------------------------------- ed25519


def test_base_point_on_curve_and_order():
    x, y = ed.B_X, ed.B_Y
    # −x² + y² = 1 + d·x²·y²  (twisted Edwards, a = −1)
    assert (-x * x + y * y) % ed.P == (1 + ed.D * x * x % ed.P * y * y) % ed.P
    assert ed.is_identity(ed.scalar_mult(ed.Q, ed.BASE))
    assert not ed.is_identity(ed.scalar_mult(ed.Q - 1, ed.BASE))


def test_group_law_consistency():
    p2 = ed.point_double(ed.BASE)
    assert ed.point_equal(p2, ed.point_add(ed.BASE, ed.BASE))
    # (a + b)·B == a·B + b·B
    a, b = 12345, 67890
    lhs = ed.base_mult(a + b)
    rhs = ed.point_add(ed.base_mult(a), ed.base_mult(b))
    assert ed.point_equal(lhs, rhs)
    # P + (−P) = 0
    assert ed.is_identity(ed.point_add(p2, ed.point_neg(p2)))


def test_compress_decompress_roundtrip():
    for k in (1, 2, 7, 12345, ed.Q - 1):
        p = ed.base_mult(k)
        enc = ed.point_compress(p)
        dec = ed.point_decompress(enc)
        assert dec is not None and ed.point_equal(p, dec)
    assert ed.point_decompress(b"\xff" * 32) is None  # y >= p
    assert ed.point_decompress(b"\x00" * 31) is None  # wrong length


def test_rfc8032_public_key_vector():
    # RFC 8032 §7.1 TEST 1: secret seed -> public key
    seed = bytes.fromhex(
        "9d61b19deffd5a60ba844af492ec2cc44449c5697b326919703bac031cae7f60"
    )
    assert ed.public_key(seed).hex() == (
        "d75a980182b10ab7d54bfed3c964073a0ee172f3daa62325af021a68f707511a"
    )


# ----------------------------------------------------------------------- vrf


def test_vrf_prove_verify_roundtrip():
    key = VRFKey(seed=hashlib.sha256(b"peer-3-roles").digest())
    alpha = hashlib.sha256(b"block-hash-7").digest()
    beta, pi = key.prove(alpha)
    assert len(beta) == 64 and len(pi) == PROOF_LEN
    assert verify(key.public, alpha, pi) == beta


def test_vrf_deterministic_and_unique_per_input():
    key = VRFKey(seed=b"\x11" * 32)
    b1, p1 = key.prove(b"alpha")
    b2, p2 = key.prove(b"alpha")
    assert b1 == b2 and p1 == p2
    b3, _ = key.prove(b"beta")
    assert b3 != b1


def test_vrf_rejects_forgeries():
    key = VRFKey(seed=b"\x22" * 32)
    other = VRFKey(seed=b"\x33" * 32)
    alpha = b"round-entropy"
    beta, pi = key.prove(alpha)
    # wrong key, wrong input, tampered proof, malformed proof
    assert verify(other.public, alpha, pi) is None
    assert verify(key.public, b"other-input", pi) is None
    bad = bytearray(pi)
    bad[40] ^= 1
    assert verify(key.public, alpha, bytes(bad)) is None
    assert verify(key.public, alpha, pi[:-1]) is None
    assert verify(b"\x00" * 32, alpha, pi) is None


# --------------------------------------------------------------------- roles


def _stake(n, default=10):
    return {i: default for i in range(n)}


def test_lottery_tickets_proportional():
    stake = {0: 1, 1: 3, 2: 0}
    t = R.lottery_tickets(stake, 3)
    assert t == [0, 1, 1, 1]
    with pytest.raises(ValueError):
        R.lottery_tickets({0: 0}, 1)


def test_committees_deterministic_across_peers():
    stake = _stake(10)
    h = hashlib.sha256(b"latest-block").digest()
    a = R.elect_committees(stake, h, 3, 3, 10)
    b = R.elect_committees(stake, h, 3, 3, 10)
    assert a == b
    v, m = a
    assert len(v) == 3 and len(set(v)) == 3
    assert len(m) == 3 and len(set(m)) == 3
    # different block hash -> (almost surely) different committees
    h2 = hashlib.sha256(b"other-block").digest()
    assert R.elect_committees(stake, h2, 3, 3, 10) != a


def test_stake_biases_the_draw():
    # one node holding ~all stake wins essentially every seat
    stake = {0: 10_000, 1: 1, 2: 1}
    wins = 0
    for r in range(20):
        h = hashlib.sha256(f"blk{r}".encode()).digest()
        v, _ = R.elect_committees(stake, h, 1, 0, 3)
        wins += v[0] == 0
    assert wins >= 18


def test_entropy_exhaustion_rehashes():
    # 2 bytes of entropy yields exactly one window, then must re-hash;
    # drawing many distinct winners forces that path
    t = list(range(50))
    winners = R.draw_winners(b"\xaa\xbb", [i for i in t for _ in range(1)], 20)
    assert len(winners) == 20 and len(set(winners)) == 20


def test_draw_winners_excludes_and_bounds():
    tickets = R.lottery_tickets(_stake(5), 5)
    w = R.draw_winners(b"seed-entropy-string", tickets, 4, exclude=2)
    assert 2 not in w and len(set(w)) == 4
    with pytest.raises(ValueError):
        R.draw_winners(b"seed", tickets, 5, exclude=2)  # only 4 distinct left


def test_noiser_draw_verifies_and_binds():
    stake = _stake(8)
    h = hashlib.sha256(b"blk").digest()
    key = VRFKey(seed=b"\x44" * 32)
    draw = R.elect_noisers(key, stake, h, source_id=1, num_noisers=2,
                           total_nodes=8)
    assert 1 not in draw.noisers and len(draw.noisers) == 2
    assert R.verify_noiser_draw(key.public, stake, h, 1, draw, 8)
    # a lying requester substituting its favorite noisers fails verification
    forged = R.NoiserDraw(noisers=[2, 3], output=draw.output, proof=draw.proof)
    if forged.noisers != draw.noisers:
        assert not R.verify_noiser_draw(key.public, stake, h, 1, forged, 8)
    # proof from a different key fails
    other = VRFKey(seed=b"\x55" * 32)
    assert not R.verify_noiser_draw(other.public, stake, h, 1, draw, 8)


def test_role_map_prime_codec():
    rm = R.RoleMap.build(6, verifiers=[0, 1], miners=[1, 2], noisers=[3])
    assert rm.roles[0] == 2 and rm.roles[1] == 6 and rm.roles[2] == 3
    assert rm.roles[3] == 5 and rm.roles[4] == 1
    assert rm.is_verifier(0) and rm.is_verifier(1) and not rm.is_verifier(2)
    assert rm.is_miner(1) and rm.is_miner(2) and not rm.is_miner(3)
    assert rm.is_noiser(3) and not rm.is_noiser(0)
    # vanilla = role 1 or noiser-only (ref: main.go:539-541)
    assert rm.is_vanilla(3) and rm.is_vanilla(4) and not rm.is_vanilla(0)
    verifiers, miners, noisers, vanilla = rm.committee()
    assert verifiers == [0, 1]  # sorted, ref main.go:560-562
    assert set(miners) == {1, 2} and noisers == [3] and vanilla == 3
