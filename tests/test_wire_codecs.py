"""Wire data plane: codec round-trips, negotiation/fallback, chunked
streaming, byte accounting, and hostile-input rejection
(runtime/codecs.py + messages.py + rpc.py, docs/WIRE_PLANE.md).

The load-bearing invariant everywhere: the WIRE is always bit-exact —
all lossiness happens in the protocol-plane `transform` BEFORE
commitment — so decode(encode(transform(x))) == transform(x) to the bit,
and crypto-bearing arrays travel verbatim.
"""

import asyncio
import struct

import numpy as np
import pytest

from biscotti_tpu.ledger.block import Update
from biscotti_tpu.runtime import codecs as wcodecs
from biscotti_tpu.runtime import messages as msgs
from biscotti_tpu.runtime import rpc, wire

pytestmark = pytest.mark.codec

CODECS = ["zlib", "f32", "bf16", "topk", "f32+zlib", "bf16+zlib",
          "topk+f32+zlib"]


def _roundtrip(name, arrays, codec):
    frame = msgs.encode(name, {"k": 1}, arrays, codec=codec)
    mt, meta, out = msgs.decode(frame[4:])
    assert mt == name
    return meta, out, len(frame)


# ------------------------------------------------------------ round-trips

@pytest.mark.parametrize("codec", CODECS)
def test_transform_then_wire_is_bit_exact(codec):
    rng = np.random.default_rng(7)
    x = np.trunc(rng.normal(0, 0.02, 4096) * 1e4) / 1e4  # quantized delta
    wc = wcodecs.get(codec)
    y, _ = wc.transform(x, topk_k=200)
    meta, out, _ = _roundtrip("T", {"d": y}, codec)
    assert out["d"].dtype == np.float64
    assert np.array_equal(out["d"], y), codec
    # idempotence: the transform is a projection
    y2, _ = wc.transform(y, topk_k=200)
    assert np.array_equal(y2, y), codec
    if not wc.lossy:
        assert np.array_equal(y, x)


@pytest.mark.parametrize("codec", CODECS)
def test_full_precision_payload_survives_coded_frame(codec):
    """A payload that never went through the lossy transform (e.g. a
    block minted by a raw64 peer) must cross a codec-negotiated link
    unchanged: downcast stages skip when inexact, zlib is lossless."""
    rng = np.random.default_rng(11)
    x = rng.normal(size=2048)  # full-entropy f64: f32/bf16 NOT exact
    _, out, _ = _roundtrip("T", {"d": x}, codec)
    assert np.array_equal(out["d"], x), codec


def test_crypto_arrays_always_travel_raw():
    rng = np.random.default_rng(3)
    arrays = {
        "share_rows": rng.integers(0, 2**62, (4, 16)).astype(np.int64),
        "comms": rng.integers(0, 256, (16, 10, 64)).astype(np.uint8),
        "d": np.trunc(rng.normal(0, 1, 512) * 1e4) / 1e4,
    }
    parts = msgs.encode_parts("T", {}, arrays, codec="f32+zlib")
    header = __import__("json").loads(bytes(parts[2]).decode())
    descs = {d["name"]: d for d in header["arrays"]}
    assert "codec" not in descs["share_rows"]  # int64: verbatim
    assert "codec" not in descs["comms"]  # uint8: verbatim
    assert descs["d"].get("codec")  # float payload: coded
    _, out, _ = _roundtrip("T", arrays, "f32+zlib")
    for k, v in arrays.items():
        assert np.array_equal(out[k], v), k


def test_codec_roundtrip_property():
    hypothesis = pytest.importorskip(
        "hypothesis", reason="property-based deps absent in this env")
    from hypothesis import given, settings, strategies as st

    @settings(max_examples=40, deadline=None)
    @given(
        codec=st.sampled_from(CODECS),
        d=st.integers(min_value=1, max_value=300),
        k=st.integers(min_value=1, max_value=64),
        scale=st.sampled_from([1e-6, 1e-2, 1.0, 1e4]),
        seed=st.integers(min_value=0, max_value=2**31 - 1),
    )
    def check(codec, d, k, scale, seed):
        rng = np.random.default_rng(seed)
        x = rng.normal(0, scale, d)
        x[rng.random(d) < 0.3] = 0.0  # realistic zero support
        wc = wcodecs.get(codec)
        y, res = wc.transform(x, topk_k=k)
        _, out, _ = _roundtrip("T", {"d": y}, codec)
        assert np.array_equal(out["d"], y)
        if wc.sparsify:
            # error feedback: kept + residual == input, exactly what
            # the next round's delta gets back
            assert res is not None and res.shape == x.shape
        # a full-precision payload is never altered by the wire
        _, out2, _ = _roundtrip("T", {"d": x}, codec)
        assert np.array_equal(out2["d"], x)

    check()


def test_unpack_update_zero_copy_on_matching_dtype():
    d = np.arange(64, dtype=np.float64)
    meta, arrays = wire.pack_update(
        Update(source_id=1, iteration=2, delta=d, commitment=b"\0" * 32))
    u = wire.unpack_update(meta, arrays)
    assert np.shares_memory(u.delta, arrays["u.delta"])  # no decode copy
    u32 = wire.unpack_update(meta, {"u.delta": d.astype(np.float32)})
    assert u32.delta.dtype == np.float64  # converted, not aliased


# ------------------------------------------------------- hostile payloads

def test_zlib_bomb_rejected():
    import json
    import zlib

    # a few KB of compressed zeros claiming a shape whose decoded size
    # blows past MAX_FRAME: refused BEFORE any inflate is attempted
    bomb = zlib.compress(b"\0" * 65536, 9)
    header = json.dumps({
        "type": "T", "meta": {}, "codec": "zlib",
        "arrays": [{"name": "d", "dtype": "float64",
                    "shape": [msgs.MAX_FRAME], "codec": "zlib",
                    "nbytes": len(bomb)}],
    }, separators=(",", ":")).encode()
    payload = struct.pack(">I", len(header)) + header + bomb
    with pytest.raises(msgs.CodecError):
        msgs.decode(payload)

    # a stream that inflates past what its declared shape needs
    header2 = json.dumps({
        "type": "T", "meta": {}, "codec": "zlib",
        "arrays": [{"name": "d", "dtype": "float64", "shape": [8],
                    "codec": "zlib", "nbytes": len(bomb)}],
    }, separators=(",", ":")).encode()
    payload2 = struct.pack(">I", len(header2)) + header2 + bomb
    with pytest.raises(msgs.CodecError):
        msgs.decode(payload2)


def test_hostile_coded_frames_rejected_not_crash():
    good = msgs.encode("T", {}, {"d": np.ones(32)}, codec="topk+f32+zlib")
    # flip bytes through the coded section: every corruption must raise
    # CodecError (or decode to something), never segfault/hang
    for off in range(40, min(len(good), 120), 7):
        bad = bytearray(good[4:])
        bad[off] ^= 0xFF
        try:
            msgs.decode(bytes(bad))
        except msgs.CodecError:
            pass

    # unknown / malformed codec tags
    import json
    for tag in ["nope", "f32+f32", "f32+bf16", "", "raw64+zlib"]:
        header = json.dumps({
            "type": "T", "meta": {},
            "arrays": [{"name": "d", "dtype": "float64", "shape": [4],
                        "codec": tag, "nbytes": 8}],
        }, separators=(",", ":")).encode()
        payload = struct.pack(">I", len(header)) + header + b"\0" * 8
        with pytest.raises(msgs.CodecError):
            msgs.decode(payload)


def test_sparse_indices_validated():
    import json

    # duplicate / out-of-range indices must be refused (a hostile scatter
    # could otherwise mis-shape the decoded update)
    k = 3
    packed = (struct.pack("<Q", k)
              + np.array([5, 5, 2], "<i4").tobytes()
              + np.zeros(3, "<f8").tobytes())
    header = json.dumps({
        "type": "T", "meta": {},
        "arrays": [{"name": "d", "dtype": "float64", "shape": [8],
                    "codec": "topk", "nbytes": len(packed)}],
    }, separators=(",", ":")).encode()
    payload = struct.pack(">I", len(header)) + header + packed
    with pytest.raises(msgs.CodecError):
        msgs.decode(payload)


# ------------------------------------------------------ chunked streaming

def test_chunk_split_and_reassembly_unit():
    rng = np.random.default_rng(5)
    x = rng.normal(size=40_000)  # ~320 KB, incompressible
    blob = msgs.encode("T", {"n": 1}, {"d": x}, chunk_bytes=65536)
    # multiple chunk frames on the wire…
    off, n_frames = 0, 0
    while off < len(blob):
        (ln,) = struct.unpack(">I", blob[off: off + 4])
        off += 4 + ln
        n_frames += 1
    assert n_frames > 1
    # …that FrameStream reassembles into ONE frame
    fs = rpc.FrameStream()
    fs._acc += blob
    fs._drain_acc()
    payload = fs._frames.get_nowait()
    assert fs._frames.empty()
    mt, meta, out = msgs.decode(payload)
    assert mt == "T" and np.array_equal(out["d"], x)


def test_chunk_reassembly_enforces_max_frame(monkeypatch):
    monkeypatch.setattr(msgs, "MAX_FRAME", 10_000)
    fs = rpc.FrameStream()
    chunk = msgs.CHUNK_MAGIC + b"\x00" + b"x" * 6000
    fs._enqueue(chunk)
    assert fs._exc is None
    fs._enqueue(chunk)  # reassembled total 12 KB > cap
    assert fs._exc is not None
    assert fs._frames.empty()


def test_chunked_rpc_roundtrip_live():
    """Request AND reply above the chunk threshold over a real loopback
    connection: client chunks via chunk_bytes, server honours achunk."""
    rng = np.random.default_rng(9)
    big = rng.normal(size=60_000)  # ~480 KB each way

    async def handler(msg_type, meta, arrays):
        return {"ok": 1}, {"echo": arrays["d"]}

    async def go():
        server = rpc.RPCServer("127.0.0.1", 13490, handler)
        server.caps = wcodecs.FULL_CAPS
        await server.start()
        pool = rpc.Pool()
        try:
            rmeta, rarrays = await pool.call(
                "127.0.0.1", 13490, "Big",
                {"achunk": 65536}, {"d": big},
                timeout=20.0, chunk_bytes=65536)
            return rmeta, rarrays
        finally:
            pool.close()
            await server.stop()

    rmeta, rarrays = asyncio.run(go())
    assert rmeta["ok"] == 1
    assert np.array_equal(rarrays["echo"], big)


# ------------------------------------------------- live cluster behavior

def _wire_out_by_codec(results, msg_type=None):
    tot = {}
    for r in results:
        fam = r["telemetry"]["metrics"].get("biscotti_wire_bytes_total", {})
        for row in fam.get("series", []):
            lb = row["labels"]
            if lb.get("direction") != "out":
                continue
            if msg_type is not None and lb.get("msg_type") != msg_type:
                continue
            tot[lb.get("codec")] = tot.get(lb.get("codec"), 0) \
                + row["value"]
    return tot


def _cluster(port, dataset, codecs_by_node, iters=2, **kw):
    from biscotti_tpu.config import BiscottiConfig, Defense, Timeouts
    from biscotti_tpu.runtime.peer import PeerAgent

    fast = Timeouts(update_s=6.0, block_s=30.0, krum_s=6.0, share_s=6.0,
                    rpc_s=8.0)
    n = len(codecs_by_node)
    base = dict(num_nodes=n, dataset=dataset, base_port=port,
                num_verifiers=1, num_miners=1, num_noisers=1,
                secure_agg=True, noising=True, verification=True,
                defense=Defense.KRUM, max_iterations=iters,
                convergence_error=0.0, sample_percent=1.0, batch_size=8,
                timeouts=fast, seed=3)
    base.update(kw)
    cfgs = [BiscottiConfig(node_id=i, wire_codec=codecs_by_node[i], **base)
            for i in range(n)]

    async def go():
        agents = [PeerAgent(c) for c in cfgs]
        results = await asyncio.gather(*(a.run() for a in agents))
        return agents, results

    return asyncio.run(go())


def test_mixed_cluster_interop_raw64_peer_converges():
    """One raw64-only peer among codec-enabled peers: negotiation must
    fall back per-link, crypto must survive, chains must agree."""
    agents, results = _cluster(
        13410, "creditcard", ["raw64", "f32+zlib", "f32+zlib", "f32+zlib"])
    dumps = [r["chain_dump"] for r in results]
    assert all(d == dumps[0] for d in dumps)
    assert sum(a.counters.get("submission_rejected", 0)
               for a in agents) == 0
    assert sum(a.counters.get("secret_registered", 0) for a in agents) > 0
    # the legacy peer sent ONLY raw64 frames…
    raw_only = _wire_out_by_codec([results[0]])
    assert set(raw_only) == {"raw64"} and raw_only["raw64"] > 0
    # …codec peers spoke BOTH dialects: raw64 toward the legacy peer,
    # f32+zlib among themselves
    coded = _wire_out_by_codec(results[1:])
    assert coded.get("f32+zlib", 0) > 0
    assert coded.get("raw64", 0) > 0


def test_gossip_compression_vs_raw64_mnist():
    """f32+zlib vs raw64 on the SAME mnist config: block-gossip bytes
    per round must shrink substantially (>= 2x here; the mnist_cnn
    acceptance run below asserts the ISSUE's >= 3x), with secure-agg
    recovery and commitment verification intact in both runs."""
    _, res_raw = _cluster(13420, "mnist", ["raw64"] * 4, noising=False)
    agents, res_cod = _cluster(13430, "mnist", ["f32+zlib"] * 4,
                               noising=False)
    for results in (res_raw, res_cod):
        dumps = [r["chain_dump"] for r in results]
        assert all(d == dumps[0] for d in dumps)
    assert sum(a.counters.get("submission_rejected", 0)
               for a in agents) == 0
    assert sum(a.counters.get("secret_registered", 0) for a in agents) > 0
    gossip_raw = sum(_wire_out_by_codec(res_raw, "RegisterBlock").values())
    gossip_cod = sum(_wire_out_by_codec(res_cod, "RegisterBlock").values())
    assert gossip_raw > 0 and gossip_cod > 0
    assert gossip_raw / gossip_cod >= 2.0, (gossip_raw, gossip_cod)
    # both runs trained: finite errors on the shared split
    assert all(np.isfinite(r["final_error"]) for r in res_raw + res_cod)


@pytest.mark.slow
def test_acceptance_mnist_cnn_f32_zlib_3x_fewer_gossip_bytes():
    """ISSUE 4 acceptance: a 4-node live cluster with f32+zlib gossip
    shows >= 3x fewer gossip bytes/round than raw64 on the mnist_cnn
    config, with share recovery and commitment verification passing and
    final error matching within noise."""
    _, res_raw = _cluster(13440, "mnist", ["raw64"] * 4,
                          noising=False, model_name="mnist_cnn")
    agents, res_cod = _cluster(13450, "mnist", ["f32+zlib"] * 4,
                               noising=False, model_name="mnist_cnn")
    for results in (res_raw, res_cod):
        dumps = [r["chain_dump"] for r in results]
        assert all(d == dumps[0] for d in dumps)
    assert sum(a.counters.get("submission_rejected", 0)
               for a in agents) == 0
    assert sum(a.counters.get("secret_registered", 0) for a in agents) > 0
    rounds_raw = max(r["iterations"] for r in res_raw)
    rounds_cod = max(r["iterations"] for r in res_cod)
    per_raw = sum(_wire_out_by_codec(res_raw, "RegisterBlock").values()) \
        / max(1, rounds_raw)
    per_cod = sum(_wire_out_by_codec(res_cod, "RegisterBlock").values()) \
        / max(1, rounds_cod)
    assert per_raw / per_cod >= 3.0, (per_raw, per_cod)
    err_raw = np.median([r["final_error"] for r in res_raw])
    err_cod = np.median([r["final_error"] for r in res_cod])
    assert abs(err_raw - err_cod) <= 0.2, (err_raw, err_cod)


# ------------------------------------------------------------ negotiation

def test_negotiation_and_capabilities():
    assert wcodecs.negotiate("f32+zlib", wcodecs.FULL_CAPS) == "f32+zlib"
    assert wcodecs.negotiate("f32+zlib", wcodecs.RAW_CAPS) == "raw64"
    assert wcodecs.negotiate("raw64", wcodecs.FULL_CAPS) == "raw64"
    assert wcodecs.negotiate("garbage+zlib", wcodecs.FULL_CAPS) == "raw64"
    assert wcodecs.capabilities("raw64") == wcodecs.RAW_CAPS
    assert "chunk" in wcodecs.capabilities("zlib")
    # canonical stage ordering regardless of spelling
    assert wcodecs.canonical("zlib+f32") == "f32+zlib"
    with pytest.raises(wcodecs.WireCodecError):
        wcodecs.parse_codec("f32+bf16")


def test_config_rejects_bad_codec():
    from biscotti_tpu.config import BiscottiConfig

    with pytest.raises(ValueError):
        BiscottiConfig(wire_codec="f64+lzma")
    cfg = BiscottiConfig(wire_codec="topk+f32+zlib")
    assert cfg.wire_codec == "topk+f32+zlib"
