"""Every model-zoo family trains a real round end-to-end through the
simulator — softmax, logreg, SVM, mnist CNN, cifar LeNet, lfw CNN
(ref: the ML/Pytorch model files and ml_main_* harness family). Guards
against a family existing in the zoo but being broken in the actual
round pipeline (flat-grad reshape, loss shapes, dataset dims)."""

import jax.numpy as jnp
import numpy as np
import pytest

from biscotti_tpu.config import BiscottiConfig, Defense
from biscotti_tpu.parallel.sim import Simulator

FAMILIES = [
    ("creditcard", ""),          # logreg (dataset default)
    ("mnist", ""),               # softmax
    ("mnist", "svm"),            # multiclass hinge
    ("mnist", "mnist_cnn"),      # conv stack
    ("cifar", "cifar_cnn"),      # LeNet-5
    ("lfw", "lfw_cnn"),          # face CNN (d_in 8742)
]


@pytest.mark.parametrize("dataset,model_name", FAMILIES)
def test_family_trains_one_round(dataset, model_name):
    cfg = BiscottiConfig(
        dataset=dataset, model_name=model_name, num_nodes=4, batch_size=4,
        noising=False, verification=True, defense=Defense.KRUM,
        sample_percent=1.0, num_verifiers=0, num_miners=0, seed=1,
    )
    sim = Simulator(cfg)
    w, stake = sim.init_state()
    w2, stake2, mask, err = sim.round_step(w, stake, 0)
    assert bool(jnp.all(jnp.isfinite(w2)))
    assert float(jnp.abs(w2).max()) > 0, "round produced a zero update"
    assert 0.0 <= float(err) <= 1.0
    # a second round from the new weights also works (reshape round-trip)
    w3, _, _, err2 = sim.round_step(w2, stake2, 1)
    assert bool(jnp.all(jnp.isfinite(w3)))
